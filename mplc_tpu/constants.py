"""Framework-wide constants.

Mirrors the parameter surface of the reference simulator
(/root/reference/mplc/constants.py:1-55) so that configurations written for the
reference keep their meaning here.
"""

# ML defaults (reference: mplc/constants.py:7-12)
DEFAULT_BATCH_SIZE = 256
MAX_BATCH_SIZE = 2 ** 20
DEFAULT_GRADIENT_UPDATES_PER_PASS_COUNT = 8
PATIENCE = 10  # early-stopping patience, in epochs
DEFAULT_BATCH_COUNT = 20
DEFAULT_EPOCH_COUNT = 40

# Logging file names (reference: mplc/constants.py:17-18)
INFO_LOGGING_FILE_NAME = "info.log"
DEBUG_LOGGING_FILE_NAME = "debug.log"

# Paths
EXPERIMENTS_FOLDER_NAME = "experiments"

# Quick-demo shrink sizes (reference: mplc/constants.py:24-26)
TRAIN_SET_MAX_SIZE_QUICK_DEMO = 1000
VAL_SET_MAX_SIZE_QUICK_DEMO = 500
TEST_SET_MAX_SIZE_QUICK_DEMO = 500

# Contributivity method registry names (reference: mplc/constants.py:28-43)
CONTRIBUTIVITY_METHODS = [
    "Shapley values",
    "Independent scores",
    "TMCS",
    "ITMCS",
    "IS_lin_S",
    "IS_reg_S",
    "AIS_Kriging_S",
    "SMCS",
    "WR_SMC",
    "Federated SBS linear",
    "Federated SBS quadratic",
    "Federated SBS constant",
    "LFlip",
    "PVRL",
    # Retrain-free family (this framework, beyond the reference registry):
    # coalition models are RECONSTRUCTED from per-partner updates recorded
    # during one grand-coalition training run (contrib/reconstruct.py), so
    # v(S) costs one eval-only batch instead of a full retrain.
    "GTG-Shapley",
    "SVARM",
    # Adaptive query planner (contrib/planner.py): routes (game size,
    # accuracy target, deadline) to exact/GTG/SVARM/DPVS-pruned using
    # banked devcost estimates; the resolved plan is journaled so a
    # replay runs the same concrete method.
    "auto",
]

# Dataset tags (reference: mplc/constants.py:46-52)
MNIST = "mnist"
CIFAR10 = "cifar10"
TITANIC = "titanic"
ESC50 = "esc50"
IMDB = "imdb"
SUPPORTED_DATASETS_NAMES = [MNIST, CIFAR10, TITANIC, ESC50, IMDB]

# TPU-specific knobs (new in this framework)
# Max number of coalitions evaluated in a single compiled batch per device;
# larger requests are chunked so HBM stays bounded.
MAX_COALITIONS_PER_DEVICE_BATCH = 16
# Chunk size (samples) for validation/test-set evaluation inside jit, to bound
# the [coalitions x partners x samples] activation footprint. Env-overridable
# (MPLC_TPU_EVAL_CHUNK) so the coalition-cap crash bisect can halve the eval
# window to test whether wide-batch worker crashes are program-shape-bound
# (perf/r4/tune_cap32.log; VERDICT r4 weak #3).
#
# NOTE: read ONCE at import time — setting MPLC_TPU_EVAL_CHUNK after
# `import mplc_tpu` has no effect (eval sets are chunked when built, and
# the chunk shape is baked into the compiled programs). A malformed or
# non-positive value falls back to the default with a warning instead of
# crashing every import of the package (including the bench's CPU-fallback
# re-exec, were the knob to leak into its environment).
import os as _os


def _env_positive_int(name: str, default: int) -> int:
    raw = _os.environ.get(name)
    if raw is None:
        return default
    try:
        value = int(raw)
        if value <= 0:
            raise ValueError(raw)
    except ValueError:
        import warnings
        warnings.warn(f"{name}={raw!r} is not a positive integer; "
                      f"falling back to {default}", stacklevel=2)
        return default
    return value


def _env_nonneg_int(name: str, default: int) -> int:
    """Same warn+fallback contract as `_env_positive_int`, for integer
    knobs where an explicit 0 is a documented value (e.g.
    MPLC_TPU_SVARM_SAMPLES=0 meaning auto) and must not warn."""
    raw = _os.environ.get(name)
    if raw is None:
        return default
    try:
        value = int(raw)
        if value < 0:
            raise ValueError(raw)
    except ValueError:
        import warnings
        warnings.warn(f"{name}={raw!r} is not a non-negative integer; "
                      f"falling back to {default}", stacklevel=2)
        return default
    return value


def _env_nonneg_float(name: str, default: float) -> float:
    """Same warn+fallback contract as `_env_positive_int`, for knobs where
    zero is meaningful (e.g. a retry backoff of 0 s in the fast tier)."""
    raw = _os.environ.get(name)
    if raw is None:
        return default
    try:
        value = float(raw)
        if value < 0 or value != value:  # NaN guard
            raise ValueError(raw)
    except ValueError:
        import warnings
        warnings.warn(f"{name}={raw!r} is not a non-negative number; "
                      f"falling back to {default}", stacklevel=2)
        return default
    return value


EVAL_CHUNK_SIZE = _env_positive_int("MPLC_TPU_EVAL_CHUNK", 2048)

# Fused wide-step mode (MPLC_TPU_STEP_WIDTH_MULT=k): fold k consecutive
# gradient_updates_per_pass sub-batches into ONE k-x-wider SGD step inside
# every multi-partner pass. k=1 (the default) is bit-identical to the
# historical per-sub-batch stepping; k>1 is an OPT-IN DOCUMENTED DEVIATION
# from the reference trajectory (fewer, wider optimizer updates per
# minibatch — ceil(gup/k) instead of gup) that raises per-step arithmetic
# intensity on MXU-hostile small sub-batches. Read once at import time,
# same contract as MPLC_TPU_EVAL_CHUNK: the step grid is baked into the
# compiled programs, and a malformed value warns + falls back to 1.
STEP_WIDTH_MULT = _env_positive_int("MPLC_TPU_STEP_WIDTH_MULT", 1)

# Ceiling for the HBM-derived coalitions-per-device autotune
# (contrib/engine.py _device_batch_cap). 16 is the measured sweet spot for
# per-size slot programs (cap-32 bisect, perf/r4/tune_cap32.log); with
# MPLC_TPU_SLOT_MERGE bounding the program count the ceiling is worth
# raising on chips with HBM headroom — override with
# MPLC_TPU_BATCH_CAP_CEILING (read at cap-computation time, not import).
BATCH_CAP_CEILING_ENV = "MPLC_TPU_BATCH_CAP_CEILING"

# Fault-tolerance knobs (contrib/engine.py + faults.py), all read at
# ENGINE-CONSTRUCTION time via the warn+fallback parsers above — a typo'd
# value degrades to the default instead of killing an hours-long sweep:
#   MPLC_TPU_MAX_RETRIES        transient-failure retries per batch (3)
#   MPLC_TPU_RETRY_BACKOFF_SEC  base of the exponential backoff (0.5 s,
#                               doubling per attempt, capped below)
#   MPLC_TPU_MAX_CAP_HALVINGS   OOM cap-halvings before the engine routes
#                               remaining batches through the per-batch
#                               CPU path (3)
#   MPLC_TPU_FAULT_PLAN         deterministic fault-injection plan
#                               (grammar in faults.py)
MAX_RETRIES_ENV = "MPLC_TPU_MAX_RETRIES"
RETRY_BACKOFF_ENV = "MPLC_TPU_RETRY_BACKOFF_SEC"
MAX_CAP_HALVINGS_ENV = "MPLC_TPU_MAX_CAP_HALVINGS"
RETRY_BACKOFF_CAP_SEC = 30.0  # bound on a single backoff sleep

# Partner-level fault model + trust-calibrated answers (read at
# ENGINE-CONSTRUCTION time, same warn+fallback contract as the
# fault-tolerance knobs above):
#   MPLC_TPU_PARTNER_FAULT_PLAN  deterministic partner-misbehavior plan —
#                                dropout/straggler/noisy/glabel entries
#                                (grammar in faults.py). Changes the GAME
#                                (v(S) itself), so it is part of the
#                                coalition-cache fingerprint.
#   MPLC_TPU_SEED_ENSEMBLE       K > 1 trains K seed replicas of every
#                                coalition as extra slot-batch rows through
#                                the same merged buckets (one sweep's
#                                dispatch cost, K x rows), making variance
#                                a first-class output: per-partner Shapley
#                                confidence intervals + a Kendall-tau
#                                rank-stability score in the sweep report.
PARTNER_FAULT_PLAN_ENV = "MPLC_TPU_PARTNER_FAULT_PLAN"
SEED_ENSEMBLE_ENV = "MPLC_TPU_SEED_ENSEMBLE"

# Buffer donation (mpl/engine.py jit properties + the program bank): with
# the knob at its default (on), the trainer's state-carrying jits declare
# donate_argnums on the TrainState argument, so the previous epoch-chunk's
# params/optimizer buffers are donated into each step instead of coexisting
# with the new state — roughly halving param-side HBM per in-flight batch
# and raising the HBM-derived coalition-cap autotune. Donation NEVER
# changes v(S) (bit-identity is equality-tested, tests/test_donation.py);
# MPLC_TPU_DONATE_BUFFERS=0 opts out (e.g. to bisect an aliasing bug in a
# new jaxlib). Read at jit-construction time, keyed into the per-trainer
# jit cache, so engines built after a toggle see the new policy.
DONATE_BUFFERS_ENV = "MPLC_TPU_DONATE_BUFFERS"

# Program bank (contrib/bank.py): AOT-lower + compile every slot program
# ahead of its first dispatch, overlap compilation of bucket k+1 with
# bucket k's execution on a background thread, and persist a manifest of
# compiled program keys next to the XLA persistent cache so a repeated
# sweep (or bench warm-up) can prove the bank already holds every program
# it needs. MPLC_TPU_PROGRAM_BANK=0 disables (every program then compiles
# inline at first dispatch, the pre-bank behavior).
PROGRAM_BANK_ENV = "MPLC_TPU_PROGRAM_BANK"

# Persistent XLA compilation cache (utils.enable_compile_cache_from_env):
# when set, every compiled program — the slot-pipeline trainers, the
# reconstruction eval programs, bench warm-up — is persisted to this
# directory, so a service restart or a repeated sweep pays zero residual
# compile (the first step of the ROADMAP "program bank" item; bench's
# warm-up doubles as a cache prime and the telemetry sidecar records the
# cache-hit provenance). Read wherever compilation is about to start
# (bench.main, CharacteristicEngine construction); unset = JAX default
# (no persistent cache, unless the caller configured one directly).
COMPILE_CACHE_DIR_ENV = "MPLC_TPU_COMPILE_CACHE_DIR"

# Retrain-free estimator knobs (contrib/contributivity.py GTG-Shapley /
# SVARM, warn+fallback parses at method-call time):
#   MPLC_TPU_GTG_TRUNCATION   within-round truncation threshold for
#                             GTG-Shapley's permutation scan: once
#                             |v(N) - v(prefix)| < threshold the
#                             remaining positions of that permutation are
#                             truncated (marginal ~ 0). Default 0.05.
#   MPLC_TPU_SVARM_SAMPLES    SVARM's sampled-coalition budget after the
#                             exact anchors + per-stratum warm-up;
#                             0/unset = auto (max(4 n^2, 128)).
GTG_TRUNCATION_ENV = "MPLC_TPU_GTG_TRUNCATION"
SVARM_SAMPLES_ENV = "MPLC_TPU_SVARM_SAMPLES"

# Live contributivity tier (mplc_tpu/live/): resident incremental games
# answering "what is my Shapley value NOW" from recorded-round
# reconstruction, with DPVS-style dynamic coalition pruning:
#   MPLC_TPU_LIVE_PRUNE_TAU    DPVS pruning threshold tau in [0, 1]
#                              (read at query time, warn+fallback): a
#                              partner whose recorded-round information
#                              score falls below tau x the max partner
#                              score is pruned — coalitions differing
#                              only by pruned partners collapse onto one
#                              evaluated representative. 0 (the default)
#                              = pruning OFF, queries bit-identical to
#                              the unpruned reconstruction path (the
#                              exactness-preserving off switch).
#   MPLC_TPU_LIVE_MAX_ROUNDS   resident-round cap per live game (4096,
#                              read at game construction): append_round
#                              past it raises LiveGameFull instead of
#                              letting one tenant's history grow device
#                              reconstruction cost and journal size
#                              without bound.
#   MPLC_TPU_LIVE_QUERY_DEADLINE_SEC
#                              default deadline for live-query jobs
#                              submitted through the sweep service's
#                              low-latency class (submit_live); 0/unset
#                              = no default deadline. An explicit
#                              deadline_sec argument wins.
#   MPLC_TPU_LIVE_MAX_RESIDENT cap on how many live games keep their
#                              round stacks in RAM at once (process-wide,
#                              live/residency.py; read at every residency
#                              decision). Past the cap, the
#                              least-recently-used JOURNALED game is
#                              evicted to a stub and restored from its
#                              WAL on the next touch (a latency tier, not
#                              a correctness change — evict/restore/query
#                              is bit-identical). 0/unset = unbounded
#                              (the pre-residency behavior).
#   MPLC_TPU_LIVE_INGEST       "1" enables the telemetry server's
#                              streaming-ingestion route
#                              (POST /live/<tenant>/round,
#                              obs/export.py): live_round wire triples
#                              are decoded and fed to append_round
#                              without an in-process call. Off by
#                              default — a mutating HTTP surface is an
#                              explicit operator decision.
#   MPLC_TPU_LIVE_CLUSTERS     cluster count for hierarchical/grouped
#                              Shapley queries past the 16-partner exact
#                              wall (live/hierarchy.py; read at query/
#                              plan time, warn+fallback, clamped to 16).
#                              0/unset = auto (~sqrt(P)).
#   MPLC_TPU_LIVE_CLUSTER_TAU  hierarchical clustering threshold in
#                              [0, 1] (read at query/plan time): partners
#                              whose DPVS info score falls below tau x
#                              the max score are grouped into ONE shared
#                              low-information tail cluster instead of
#                              being spread across the score-balanced
#                              clusters. 0 (default) = no tail cluster.
LIVE_PRUNE_TAU_ENV = "MPLC_TPU_LIVE_PRUNE_TAU"
LIVE_MAX_ROUNDS_ENV = "MPLC_TPU_LIVE_MAX_ROUNDS"
LIVE_QUERY_DEADLINE_ENV = "MPLC_TPU_LIVE_QUERY_DEADLINE_SEC"
LIVE_MAX_RESIDENT_ENV = "MPLC_TPU_LIVE_MAX_RESIDENT"
LIVE_INGEST_ENV = "MPLC_TPU_LIVE_INGEST"
LIVE_CLUSTERS_ENV = "MPLC_TPU_LIVE_CLUSTERS"
LIVE_CLUSTER_TAU_ENV = "MPLC_TPU_LIVE_CLUSTER_TAU"

# Sweep service (mplc_tpu/service/): the long-lived multi-tenant
# scheduler — bounded submission queue, round-robin slicing across
# tenants, per-tenant fault isolation, journaled crash recovery. All
# read at SERVICE-CONSTRUCTION time with the warn+fallback parsers:
#   MPLC_TPU_SERVICE_MAX_PENDING   admission-control bound on jobs not
#                                  yet terminal (32); past it submit()
#                                  raises ServiceOverloaded
#   MPLC_TPU_SERVICE_SLICE         coalitions per scheduling quantum for
#                                  exact sweeps (16): smaller = fairer
#                                  interleaving + tighter deadline
#                                  granularity, larger = fuller buckets
#   MPLC_TPU_SERVICE_FAULT_PLAN    deterministic service-level fault
#                                  plan, addressed by job submission
#                                  ordinal (grammar in faults.py):
#                                  crash@job2:batch3,reject@job4,
#                                  stall@job1:sec2 — plus the load
#                                  harness's seeded chaos extension
#                                  chaos@rate0.05:seed7 (every job
#                                  independently draws one random
#                                  crash/transient/stall fault with the
#                                  given probability; the draw depends
#                                  only on (seed, ordinal), so chaos
#                                  runs replay under any worker count)
#   MPLC_TPU_SERVICE_WORKERS       scheduler worker-thread pool size
#                                  (1); each worker is pinned to a
#                                  device slot (index % local devices)
#                                  and beats its own /healthz heartbeat
#   MPLC_TPU_SERVICE_PRIORITY_DEFAULT
#                                  priority tier for submit() calls that
#                                  pass none (0); higher integers are
#                                  more important — the scheduler
#                                  weights quanta by tier+1 and the
#                                  overload governor defers/sheds the
#                                  lowest tier first
#   MPLC_TPU_SERVICE_SHED_P99_SEC  overload governor threshold: when the
#                                  windowed queue-wait p99 (recent waits
#                                  + live queued ages) crosses it, the
#                                  scheduler defers then SHEDS lowest-
#                                  tier never-started jobs with a
#                                  classified JobShed. 0/unset = off.
#   MPLC_TPU_SERVICE_RETRY_FLOOR_SEC
#                                  floor under the retry_after_sec hint
#                                  ServiceOverloaded/JobShed carry
#                                  (0.05): with no queue-wait history
#                                  the windowed p50 is absent and the
#                                  hint would read 0.0 — an instruction
#                                  to hammer submit immediately. 0
#                                  restores the old behavior.
SERVICE_MAX_PENDING_ENV = "MPLC_TPU_SERVICE_MAX_PENDING"
SERVICE_SLICE_ENV = "MPLC_TPU_SERVICE_SLICE"
SERVICE_FAULT_PLAN_ENV = "MPLC_TPU_SERVICE_FAULT_PLAN"
SERVICE_WORKERS_ENV = "MPLC_TPU_SERVICE_WORKERS"
SERVICE_PRIORITY_DEFAULT_ENV = "MPLC_TPU_SERVICE_PRIORITY_DEFAULT"
SERVICE_SHED_P99_ENV = "MPLC_TPU_SERVICE_SHED_P99_SEC"
SERVICE_RETRY_FLOOR_ENV = "MPLC_TPU_SERVICE_RETRY_FLOOR_SEC"

# Numeric-truth plane (mplc_tpu/obs/numerics.py):
#   MPLC_TPU_DETERMINISTIC_REDUCE  =1 replaces every aggregation's
#                                  order-sensitive `sum`/`psum` pair with
#                                  a strict left-to-right fold in GLOBAL
#                                  partner order (sharded runs all-gather
#                                  the weighted terms over `part` first),
#                                  so the 2-D [coal x part] partner-
#                                  sharded path is BIT-IDENTICAL to the
#                                  unsharded reference. Changes v(S)
#                                  itself (a different — pinned —
#                                  reduction order), so it is part of the
#                                  coalition-cache fingerprint and a
#                                  workload knob. Resolved into TrainConfig
#                                  at construction time.
#   MPLC_TPU_NUMERICS_AUDIT        =1 turns on the per-device reduction
#                                  audit: at fence ordinals the engine
#                                  captures one audited coalition's
#                                  per-round per-partner aggregation terms
#                                  through a SEPARATE instrumented run
#                                  (the dispatched programs are never
#                                  touched — v(S) is bit-identical audit
#                                  on vs off), replays the sharded
#                                  (per-device partial + cross-shard
#                                  combine) and reference fold orders on
#                                  the host, and localizes the FIRST
#                                  divergent reduction step/leaf. A
#                                  detected divergence emits a
#                                  numerics.drift event and a flight-
#                                  recorder postmortem.
#   MPLC_TPU_NUMERICS_LEDGER       path of the value-provenance ledger
#                                  (JSON): every harvested v(S) is
#                                  recorded with its exact float bits, a
#                                  content hash and float-path metadata
#                                  (topology, device count, reduction
#                                  mode, slot width, cap rungs) keyed by
#                                  (subset bitmask, engine fingerprint) —
#                                  scripts/drift_diff.py diffs two
#                                  ledgers into per-subset ulp-distance
#                                  histograms and a ranking Kendall-tau.
DETERMINISTIC_REDUCE_ENV = "MPLC_TPU_DETERMINISTIC_REDUCE"
NUMERICS_AUDIT_ENV = "MPLC_TPU_NUMERICS_AUDIT"
NUMERICS_LEDGER_ENV = "MPLC_TPU_NUMERICS_LEDGER"

# Raw-speed plane (mpl/engine.py, ops/recon_kernel.py, contrib/planner.py)
# — optimizations LICENSED by the numeric-truth plane: every documented
# deviation they introduce is bounded by the value ledger (ulp histogram)
# and the ranking tau-b gate in scripts/bench_diff.py:
#   MPLC_TPU_PRECISION         fp32 (default) | mixed | bf16. Resolved
#                              into TrainConfig at construction time and
#                              part of the coalition-cache fingerprint,
#                              exactly like MPLC_TPU_DETERMINISTIC_REDUCE.
#                              fp32 keeps the compiled programs
#                              byte-identical to the pre-knob build.
#                              `mixed` runs model compute (fwd/bwd) in
#                              bf16 with fp32 master params, optimizer
#                              state and FedAvg aggregation — the
#                              recorded update stream and the
#                              reconstruction scan stay fp32. `bf16`
#                              additionally accumulates the
#                              reconstruction scan in bf16 (fp32 init
#                              params cast once at scan entry). Both are
#                              documented deviations: a non-fp32
#                              bench/sweep run MUST carry an fp32
#                              reference ledger pair (ulp histogram +
#                              Kendall tau-b) in its telemetry sidecar.
#   MPLC_TPU_RECON_KERNEL      auto (default) | off | force | interpret.
#                              Selects the fused Pallas reconstruction
#                              kernel (ops/recon_kernel.py) for the
#                              retrain-free batch-eval path: `auto` uses
#                              it when the backend is TPU, `off` always
#                              runs the per-round lax.scan reference,
#                              `force` requires the kernel (raises where
#                              Pallas cannot lower), `interpret` runs the
#                              kernel in Pallas interpret mode on any
#                              backend (the parity-test path). The chosen
#                              path is part of the ProgramBank recon key.
#   MPLC_TPU_PLANNER_ACCURACY  default accuracy target (trust-row CI
#                              half-width on normalized scores) the
#                              adaptive planner contracts for when a
#                              query says method="auto" without an
#                              explicit accuracy_target. Default 0.02.
#   MPLC_TPU_PLANNER_DEADLINE_SEC
#                              default deadline the planner budgets
#                              against for method="auto" queries;
#                              0/unset = no deadline (the loose-deadline
#                              routing row). An explicit deadline_sec
#                              argument wins.
PRECISION_ENV = "MPLC_TPU_PRECISION"
RECON_KERNEL_ENV = "MPLC_TPU_RECON_KERNEL"
PLANNER_ACCURACY_ENV = "MPLC_TPU_PLANNER_ACCURACY"
PLANNER_DEADLINE_ENV = "MPLC_TPU_PLANNER_DEADLINE_SEC"

PRECISION_MODES = ("fp32", "mixed", "bf16")
RECON_KERNEL_MODES = ("auto", "off", "force", "interpret")


def precision_mode() -> str:
    """MPLC_TPU_PRECISION with the warn+fallback contract of the other
    parsed knobs: an unrecognized value warns once per read and falls
    back to fp32 (never silently changes what a run computes). Read at
    TrainConfig-construction time and frozen into the config, so the
    precision a trainer compiled with can never drift from the one its
    cache fingerprint names."""
    raw = _os.environ.get(PRECISION_ENV, "").strip().lower()
    if not raw:
        return "fp32"
    if raw not in PRECISION_MODES:
        import warnings
        warnings.warn(
            f"{PRECISION_ENV}={raw!r} is not one of {PRECISION_MODES}; "
            "falling back to fp32", stacklevel=2)
        return "fp32"
    return raw


def recon_kernel_mode() -> str:
    """MPLC_TPU_RECON_KERNEL (warn+fallback to `auto`). Read when a
    ReconstructionEvaluator builds its batch-eval program."""
    raw = _os.environ.get(RECON_KERNEL_ENV, "").strip().lower()
    if not raw:
        return "auto"
    if raw not in RECON_KERNEL_MODES:
        import warnings
        warnings.warn(
            f"{RECON_KERNEL_ENV}={raw!r} is not one of "
            f"{RECON_KERNEL_MODES}; falling back to auto", stacklevel=2)
        return "auto"
    return raw

# Fleet sweep plane (mplc_tpu/parallel/fleet.py): coalition-axis
# sharding of one sweep across OS processes/hosts, merged with a
# ledger-verified equality proof:
#   MPLC_TPU_FLEET_SHARDS     caps the fleet bench's (BENCH_CONFIG=9)
#                             deterministic EQUALITY-pass shard count
#                             (effective default 4, further capped by
#                             the largest BENCH_FLEET_DEVICES point);
#                             the scaling-curve points' shard counts
#                             come from BENCH_FLEET_DEVICES itself
#   MPLC_TPU_FLEET_STATE_DIR  shared directory where each sharded
#                             SweepService process publishes its queue
#                             depth / admission state
#                             (fleet.publish_shard_state) and reads the
#                             cluster aggregate (fleet.cluster_view) —
#                             the cross-shard queue view in /healthz and
#                             in ServiceOverloaded redirect hints. Unset
#                             = single-process behavior, byte-identical.
#   MPLC_TPU_FLEET_SHARD_ID   this process's shard name in the state dir
#                             (default pid<pid>); also stamped as
#                             `fleet_shard` on every trace record
# Fleet observability plane (obs/fleet_view.py, obs/trace.py): pure
# read-side telemetry — none of these changes a computed number:
#   MPLC_TPU_FLEET_RUN_ID     the coordinator-minted fleet run id;
#                             injected into every worker env and stamped
#                             as `fleet_run` on every span/event record,
#                             so W per-shard trace streams correlate by
#                             construction (scripts/fleet_trace_merge.py)
#   MPLC_TPU_FLEET_COORD_TS   the coordinator's spawn-time clock reading
#                             for one shard; the worker echoes it in its
#                             result JSON beside its own start/end
#                             readings — the clock-offset handshake that
#                             rebases shard traces onto the coordinator
#                             clock (midpoint rule)
#   MPLC_TPU_FLEET_PEERS      comma-separated host:port /varz endpoints
#                             the fleet collector scrapes into the
#                             aggregated /fleet/metrics + /fleet/varz
#                             view (with MPLC_TPU_METRICS_TOKEN as the
#                             operator credential)
#   MPLC_TPU_FLEET_STALE_SEC  staleness bound for cluster_view (30): a
#                             shard whose published state file is older
#                             than this is flagged stale, dropped from
#                             the live set and never recommended as the
#                             least-loaded redirect target
FLEET_SHARDS_ENV = "MPLC_TPU_FLEET_SHARDS"
FLEET_STATE_DIR_ENV = "MPLC_TPU_FLEET_STATE_DIR"
FLEET_SHARD_ID_ENV = "MPLC_TPU_FLEET_SHARD_ID"
FLEET_RUN_ID_ENV = "MPLC_TPU_FLEET_RUN_ID"
FLEET_COORD_TS_ENV = "MPLC_TPU_FLEET_COORD_TS"
FLEET_PEERS_ENV = "MPLC_TPU_FLEET_PEERS"
FLEET_STALE_SEC_ENV = "MPLC_TPU_FLEET_STALE_SEC"

# Fleet router (mplc_tpu/service/router.py) — the redirect-acting front
# over N service shards:
#   MPLC_TPU_ROUTER_BUDGET           per-job routing budget (8): total
#                                    submit attempts (first + resubmits
#                                    after ServiceOverloaded/JobShed
#                                    redirects) before the failure is
#                                    surfaced classified as
#                                    RoutedJobFailed — never silently
#                                    dropped, never retried forever
#   MPLC_TPU_ROUTER_BACKOFF_SEC      base of the capped exponential
#                                    backoff between resubmits (0.05);
#                                    each attempt sleeps
#                                    max(retry_after hint,
#                                    base * 2^(attempt-1)), capped at
#                                    32x base
#   MPLC_TPU_ROUTER_REPIN_OVERLOADS  consecutive overloads from a
#                                    tenant's pinned shard before the
#                                    router breaks stickiness and
#                                    re-pins to another shard (3) — a
#                                    deliberate, journaled event, since
#                                    a re-pin costs a WAL restore of the
#                                    tenant's resident state
#   MPLC_TPU_ROUTER_FAULT_PLAN       router-level chaos plan:
#                                    `shardkill@shard<N>:sec<F>` kills
#                                    the named shard F seconds into the
#                                    run (comma-separated entries)
#   MPLC_TPU_ROUTER_SERVE            =1 grows the telemetry server the
#                                    POST /router/submit and
#                                    GET /router/job routes a ShardServer
#                                    peer exposes; off by default — a
#                                    MUTATING HTTP surface is an explicit
#                                    operator decision
ROUTER_BUDGET_ENV = "MPLC_TPU_ROUTER_BUDGET"
ROUTER_BACKOFF_ENV = "MPLC_TPU_ROUTER_BACKOFF_SEC"
ROUTER_REPIN_OVERLOADS_ENV = "MPLC_TPU_ROUTER_REPIN_OVERLOADS"
ROUTER_FAULT_PLAN_ENV = "MPLC_TPU_ROUTER_FAULT_PLAN"
ROUTER_SERVE_ENV = "MPLC_TPU_ROUTER_SERVE"


_barrier_degradation_warned = False


def deterministic_reduce_enabled() -> bool:
    """MPLC_TPU_DETERMINISTIC_REDUCE=1 (default off). Read at
    TrainConfig-construction time and frozen into the config, so the
    reduction order a trainer compiled with can never drift from the
    one its cache fingerprint names.

    If the deterministic mode is requested but the `fusion_fence`
    batching rule could not be installed (a toolchain moved the
    optimization_barrier primitive), the bit-identity contract is
    weakened — warn LOUDLY once rather than let a run report
    reduction_mode=deterministic while the fence silently no-ops."""
    on = _os.environ.get(DETERMINISTIC_REDUCE_ENV, "") == "1"
    if on:
        global _barrier_degradation_warned
        from .ops.aggregation import _BARRIER_OK
        if not _BARRIER_OK and not _barrier_degradation_warned:
            _barrier_degradation_warned = True
            import warnings
            warnings.warn(
                f"{DETERMINISTIC_REDUCE_ENV}=1 but the optimization_"
                "barrier batching rule could not be installed on this "
                "toolchain — fusion_fence is a no-op and cross-topology "
                "bit-identity is NOT guaranteed (the ordered fold still "
                "applies). Verify with the numerics ledger/drift_diff "
                "before trusting cross-topology equality.", stacklevel=2)
    return on


# Device-time accounting (mplc_tpu/obs/devcost.py):
#   MPLC_TPU_DEVICE_FENCE_RATE     fraction of device batches that run
#                                  FENCED: the engine drains any
#                                  in-flight overlap first, dispatches
#                                  the sampled batch alone, and times a
#                                  host fetch of its results — a true
#                                  device-step-seconds sample (host
#                                  fetch, not block_until_ready: the
#                                  axon tunnel does not reliably sync
#                                  the latter). Deterministic by batch
#                                  ordinal (every round(1/rate)-th
#                                  batch), so runs replay identically.
#                                  Default 1/16; 0 = off. Fencing NEVER
#                                  changes v(S) (equality-tested) — it
#                                  only moves harvest points — but it is
#                                  a workload knob: the added syncs
#                                  reshape measured wall-clock.
DEVICE_FENCE_RATE_ENV = "MPLC_TPU_DEVICE_FENCE_RATE"

# Live telemetry plane (mplc_tpu/obs/export.py + flight.py + chrome_trace):
#   MPLC_TPU_METRICS_PORT          when set, one stdlib HTTP daemon thread
#                                  serves /metrics (Prometheus text),
#                                  /healthz (liveness + worker heartbeat
#                                  age + journal status; 503 on stall)
#                                  and /varz (full JSON state incl.
#                                  program bank and service job table).
#                                  A plain port binds LOOPBACK only (the
#                                  endpoints are unauthenticated);
#                                  host:port (e.g. 0.0.0.0:9090) opts
#                                  into wider exposure. 0 = ephemeral
#                                  port (tests). Unset = NO thread or
#                                  socket is created.
#   MPLC_TPU_FLIGHT_RECORDER_DIR   where crash flight-recorder postmortem
#                                  files land (default: the working dir)
#   MPLC_TPU_FLIGHT_RECORDER_SIZE  records held in the always-on span
#                                  ring dumped on quarantine / ladder
#                                  exhaustion / journal corruption (512)
#   MPLC_TPU_CHROME_TRACE_FILE     Chrome trace-event JSON written at
#                                  interpreter exit from the span JSONL
#                                  (requires MPLC_TPU_TRACE_FILE); the
#                                  offline equivalent is
#                                  scripts/trace_to_perfetto.py
#   MPLC_TPU_METRICS_TOKEN         optional bearer token for the
#                                  telemetry endpoints: when set,
#                                  /metrics and /varz require
#                                  `Authorization: Bearer <token>`
#                                  (401 otherwise; /healthz stays open
#                                  for liveness probes) and the /varz
#                                  per-job table is tenant-REDACTED —
#                                  rows belonging to tenants other than
#                                  the `?tenant=` viewer keep only
#                                  status/priority/age under a hashed
#                                  tenant tag. Unset = the loopback
#                                  default behavior, unchanged.
METRICS_PORT_ENV = "MPLC_TPU_METRICS_PORT"
METRICS_TOKEN_ENV = "MPLC_TPU_METRICS_TOKEN"
FLIGHT_RECORDER_DIR_ENV = "MPLC_TPU_FLIGHT_RECORDER_DIR"
FLIGHT_RECORDER_SIZE_ENV = "MPLC_TPU_FLIGHT_RECORDER_SIZE"
CHROME_TRACE_ENV = "MPLC_TPU_CHROME_TRACE_FILE"

# ---------------------------------------------------------------------------
# Env-knob registry. EVERY `MPLC_TPU_*` env var the framework reads must be
# registered here with its class — tests/test_knob_hygiene.py greps the
# source tree and fails on an unregistered knob, and checks the class
# obligations below. PRs 1-3 each extended bench.py's two knob lists by
# hand; this registry makes forgetting one a test failure, not a silently
# wrong cached-replay/fallback number.
#
#   "workload": shapes the sweep or its measurement. MUST appear in both
#       bench._replay_cached_tpu_result's refusal list (a cached TPU
#       number is a DIFFERENT workload under any non-default value) and
#       bench._spawn_cpu_fallback's env-strip list (the reduced CPU child
#       must not inherit parent tuning).
#   "sidecar": observability/output plumbing only. MUST be stripped from
#       the CPU-fallback child (it writes its own sidecars) but does not
#       refuse replay.
#   "ambient": environment plumbing (data locations) with no bench
#       obligations.
ENV_KNOBS = {
    "MPLC_TPU_BATCH_CAP_CEILING": "workload",
    "MPLC_TPU_COALITIONS_PER_DEVICE": "workload",
    # workload, not sidecar: the cache changes what a measured run PAYS
    # (residual compiles land inside the timed region), so a cached TPU
    # number is not comparable to a cache-warmed run — and the CPU child
    # configures its own cache dir
    "MPLC_TPU_COMPILE_CACHE_DIR": "workload",
    # workload, not sidecar: donation changes the HBM footprint and
    # therefore the autotuned batch cap (bucket widths), and the bank
    # changes what a measured run pays in compile time
    "MPLC_TPU_DONATE_BUFFERS": "workload",
    "MPLC_TPU_PROGRAM_BANK": "workload",
    "MPLC_TPU_EVAL_CHUNK": "workload",
    "MPLC_TPU_GTG_TRUNCATION": "workload",
    "MPLC_TPU_SVARM_SAMPLES": "workload",
    # the live-tier knobs shape what a live-query bench run computes and
    # pays: the pruning threshold changes which coalitions are evaluated
    # at all, the resident-round cap bounds the reconstruction depth, and
    # the default query deadline decides which queries survive — none may
    # leak into a cached replay or the CPU-fallback child
    "MPLC_TPU_LIVE_PRUNE_TAU": "workload",
    "MPLC_TPU_LIVE_MAX_ROUNDS": "workload",
    "MPLC_TPU_LIVE_QUERY_DEADLINE_SEC": "workload",
    # the residency/ingestion/hierarchy knobs shape the live workload the
    # same way: the residency cap decides which queries pay a WAL restore
    # (the very latency a residency bench measures), the ingestion gate
    # opens a mutating HTTP surface, and the cluster count/tau decide how
    # many coalitions a hierarchical query evaluates — none may leak into
    # a cached replay or the CPU-fallback child
    "MPLC_TPU_LIVE_MAX_RESIDENT": "workload",
    "MPLC_TPU_LIVE_INGEST": "workload",
    "MPLC_TPU_LIVE_CLUSTERS": "workload",
    "MPLC_TPU_LIVE_CLUSTER_TAU": "workload",
    "MPLC_TPU_FAULT_PLAN": "workload",
    "MPLC_TPU_MAX_CAP_HALVINGS": "workload",
    "MPLC_TPU_MAX_RETRIES": "workload",
    "MPLC_TPU_NO_SLOTS": "workload",
    "MPLC_TPU_PARTNER_FAULT_PLAN": "workload",
    "MPLC_TPU_PARTNER_SHARDS": "workload",
    "MPLC_TPU_SEED_ENSEMBLE": "workload",
    # the service knobs shape the multi-tenant bench workload: the fault
    # plan injects faults, the slice reshapes bucket packing and the
    # pending bound reshapes admission — none may leak into a cached
    # replay or the CPU-fallback child
    "MPLC_TPU_SERVICE_FAULT_PLAN": "workload",
    "MPLC_TPU_SERVICE_MAX_PENDING": "workload",
    "MPLC_TPU_SERVICE_SLICE": "workload",
    # the overload-robustness knobs reshape the service workload too:
    # worker count changes concurrency (and the load-harness ceiling),
    # the default tier reshapes scheduling weights, and the shed
    # threshold decides which jobs survive an overloaded run at all
    "MPLC_TPU_SERVICE_WORKERS": "workload",
    "MPLC_TPU_SERVICE_PRIORITY_DEFAULT": "workload",
    "MPLC_TPU_SERVICE_SHED_P99_SEC": "workload",
    # the retry floor shapes every retrying client's backoff cadence (a
    # routed overload run with floor 0 is a hammer loop, not the same
    # workload), and the router knobs reshape the routed bench workload:
    # budget decides which jobs survive at all, backoff paces the
    # resubmit storm, the re-pin bound decides when stickiness breaks,
    # the fault plan kills shards, and the serve gate opens the mutating
    # routed-submit HTTP surface — none may leak into a cached replay or
    # the CPU-fallback child
    "MPLC_TPU_SERVICE_RETRY_FLOOR_SEC": "workload",
    "MPLC_TPU_ROUTER_BUDGET": "workload",
    "MPLC_TPU_ROUTER_BACKOFF_SEC": "workload",
    "MPLC_TPU_ROUTER_REPIN_OVERLOADS": "workload",
    "MPLC_TPU_ROUTER_FAULT_PLAN": "workload",
    "MPLC_TPU_ROUTER_SERVE": "workload",
    "MPLC_TPU_PIPELINE_BATCHES": "workload",
    "MPLC_TPU_RETRY_BACKOFF_SEC": "workload",
    "MPLC_TPU_SLOT_MERGE": "workload",
    "MPLC_TPU_SLOT_POW2": "workload",
    "MPLC_TPU_STEP_WIDTH_MULT": "workload",
    "MPLC_TPU_SYNTH_NOISE": "workload",
    "MPLC_TPU_SYNTH_SCALE": "workload",
    # workload, not sidecar: a fenced batch is dispatched without
    # overlap and synced through a host fetch — the sampling reshapes
    # measured wall-clock (never v(S)), so a cached TPU number from a
    # different fence rate is a different measurement protocol
    "MPLC_TPU_DEVICE_FENCE_RATE": "workload",
    # the fleet knobs reshape the fleet bench workload (shard count =
    # process topology) and wire a service process into a shared fleet
    # state dir (cross-shard admission view, per-shard identity) — none
    # may leak into a cached replay or the CPU-fallback child
    "MPLC_TPU_FLEET_SHARDS": "workload",
    "MPLC_TPU_FLEET_STATE_DIR": "workload",
    "MPLC_TPU_FLEET_SHARD_ID": "workload",
    # the staleness bound decides which shards a routed run may target
    # (a dead shard's window of false liveness), so it reshapes the
    # routed workload the same way the state dir does
    "MPLC_TPU_FLEET_STALE_SEC": "workload",
    # deterministic-reduce changes v(S) ITSELF (a pinned reduction order
    # is a different — bit-stable — game trajectory), and the audit
    # drains overlap + runs extra capture passes at fence ordinals, so
    # both reshape what a measured run computes or pays
    "MPLC_TPU_DETERMINISTIC_REDUCE": "workload",
    "MPLC_TPU_NUMERICS_AUDIT": "workload",
    # the raw-speed knobs change what a run computes (precision: v(S)
    # itself in documented-deviation modes; kernel: the reconstruction
    # program dispatched; planner defaults: WHICH estimator an auto
    # query resolves to) — none may leak into a cached replay or the
    # CPU-fallback child
    "MPLC_TPU_PRECISION": "workload",
    "MPLC_TPU_RECON_KERNEL": "workload",
    "MPLC_TPU_PLANNER_ACCURACY": "workload",
    "MPLC_TPU_PLANNER_DEADLINE_SEC": "workload",
    # the ledger is pure observability output: recording harvested value
    # bits changes nothing the run computes or pays, but the CPU-fallback
    # child must not write over the parent's ledger file
    "MPLC_TPU_NUMERICS_LEDGER": "sidecar",
    "MPLC_TPU_PROFILE_DIR": "sidecar",
    "MPLC_TPU_METRICS_TOKEN": "sidecar",
    "MPLC_TPU_TRACE_FILE": "sidecar",
    # the live telemetry plane is pure observability plumbing: none of it
    # changes what a sweep computes or pays for, but all of it must be
    # stripped from the CPU-fallback child (the child would race the
    # parent's telemetry port, flight-recorder files and Chrome-trace out)
    "MPLC_TPU_METRICS_PORT": "sidecar",
    "MPLC_TPU_FLIGHT_RECORDER_DIR": "sidecar",
    "MPLC_TPU_FLIGHT_RECORDER_SIZE": "sidecar",
    "MPLC_TPU_CHROME_TRACE_FILE": "sidecar",
    # the fleet observability knobs are trace correlation + collector
    # plumbing: read-side only, but a CPU-fallback child must not
    # inherit the parent's fleet identity (its records would masquerade
    # as a shard's) or scrape peers on its own
    "MPLC_TPU_FLEET_RUN_ID": "sidecar",
    "MPLC_TPU_FLEET_COORD_TS": "sidecar",
    "MPLC_TPU_FLEET_PEERS": "sidecar",
    "MPLC_TPU_DATA_DIR": "ambient",
}
