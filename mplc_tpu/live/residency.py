"""Bounded residency for the live contributivity tier.

PR 13's live tier keeps every game's round stack resident forever — fine
for a handful of tenants, fatal for the ROADMAP's million-tenant target.
This module is the process-wide residency manager: at most
`MPLC_TPU_LIVE_MAX_RESIDENT` games hold their round stacks (and derived
evaluator/memo state) in RAM at once. Past the cap, the
least-recently-used JOURNALED game is evicted down to a stub; its WAL
already journals every round exactly, so the next touch restores it
through the existing `live.recover` replay path. Eviction is a LATENCY
tier, not a correctness change: evict -> restore -> query is
bit-identical to never-evicted (equality-tested in
tests/test_live_residency.py, and CI gates the committed BENCH_CONFIG=10
sidecar's restored-value bits).

Admission rules:

  - `admit(game)` makes a game resident (new games at construction,
    evicted games before their WAL replay) and bumps already-resident
    games to most-recently-used. It is called under the game's own lock.
  - Only journal-backed, currently-idle games are evictable: a victim's
    lock is acquired non-blocking, so a game mid-query/append is simply
    skipped (never stalled) and the next-least-recently-used candidate
    is tried.
  - When the cap cannot be met for a game that is NOT yet resident —
    every candidate victim is journal-less or busy — admission refuses
    with `LiveResidencyFull`, carrying a `retry_after_sec` hint (the p50
    of recent WAL-restore latencies, 0.0 with no history) exactly like
    the service's `ServiceOverloaded`, so streaming clients back off
    instead of hammering. An ALREADY-resident game is never refused: the
    cap throttles growth, it does not brick live tenants.

The cap is read from the environment at every admission decision
(0/unset = unbounded, the pre-residency behavior), with a
`configure(max_resident=...)` override for benches and tests. Games are
tracked by weak reference — a dropped/closed game leaves the books on
the next scan without an unregister protocol.
"""

from __future__ import annotations

import collections
import threading
import weakref

from .. import constants
from ..obs import metrics as obs_metrics

_lock = threading.RLock()
#: LRU of resident games: id(game) -> weakref (leftmost = coldest)
_resident: "collections.OrderedDict[int, weakref.ref]" = \
    collections.OrderedDict()
#: currently-evicted games (stubs awaiting a restore): id -> weakref
_evicted: "dict[int, weakref.ref]" = {}
#: recent WAL-restore wall-clock latencies, the retry_after_sec basis
_restore_window: collections.deque = collections.deque(maxlen=64)
_totals = {"evictions": 0, "restores": 0, "last_restore_s": 0.0}
#: test/bench override for the residency cap (None = read the env knob)
_max_override: "list[int | None]" = [None]


def configure(max_resident: "int | None") -> None:
    """Override the residency cap (benches/tests); None restores the
    `MPLC_TPU_LIVE_MAX_RESIDENT` env read."""
    with _lock:
        _max_override[0] = (None if max_resident is None
                            else int(max_resident))


def reset() -> None:
    """Drop all residency bookkeeping and the cap override (test
    isolation). Games themselves are untouched — still-alive resident
    games re-enter the books on their next touch."""
    with _lock:
        _resident.clear()
        _evicted.clear()
        _restore_window.clear()
        _totals.update(evictions=0, restores=0, last_restore_s=0.0)
        _max_override[0] = None


def max_resident() -> int:
    """The current cap (0 = unbounded)."""
    with _lock:
        if _max_override[0] is not None:
            return _max_override[0]
    return constants._env_nonneg_int(constants.LIVE_MAX_RESIDENT_ENV, 0)


def retry_after_sec() -> float:
    """Backoff hint for residency refusals: the p50 of recent
    WAL-restore latencies (nearest-rank, the admission-controller
    convention), 0.0 with no restore history."""
    with _lock:
        waits = sorted(_restore_window)
    if not waits:
        return 0.0
    idx = max(0, (len(waits) + 1) // 2 - 1)
    return float(waits[idx])


def _prune_dead() -> None:
    """Drop entries whose game was garbage-collected. Caller holds
    `_lock`."""
    for gid in [g for g, ref in _resident.items() if ref() is None]:
        del _resident[gid]
    for gid in [g for g, ref in _evicted.items() if ref() is None]:
        del _evicted[gid]


def _evict_one(exclude_id: int) -> bool:
    """Evict the least-recently-used evictable game (journal-backed and
    idle — its lock must be acquirable without blocking). Caller holds
    `_lock`. Returns False when no candidate qualifies."""
    for gid in list(_resident):
        if gid == exclude_id:
            continue
        game = _resident[gid]()
        if game is None:
            del _resident[gid]
            continue
        if game._journal is None:
            continue
        if not game._lock.acquire(blocking=False):
            continue  # mid-query/append: skip, never stall a live tenant
        try:
            if game._evict_locked():  # books updated via note_evicted
                return True
        finally:
            game._lock.release()
    return False


def note_evicted(game) -> None:
    """Record one eviction (called by `LiveGame._evict_locked`, whether
    manager-driven or operator/test-driven)."""
    with _lock:
        gid = id(game)
        _resident.pop(gid, None)
        _evicted[gid] = weakref.ref(game)
        _totals["evictions"] += 1
        _set_gauges()


def admit(game) -> None:
    """Make `game` resident (or bump it to most-recently-used), evicting
    LRU victims past the cap. Raises `LiveResidencyFull` only when the
    game is not yet resident and no victim can be evicted. Called under
    the game's own lock."""
    cap = max_resident()
    with _lock:
        _prune_dead()
        gid = id(game)
        was_resident = gid in _resident
        _evicted.pop(gid, None)
        _resident[gid] = weakref.ref(game)
        _resident.move_to_end(gid)
        while cap and len(_resident) > cap:
            if _evict_one(gid):
                continue
            if was_resident:
                break  # cap throttles growth, never bricks a live tenant
            del _resident[gid]
            from .game import LiveResidencyFull
            raise LiveResidencyFull(
                f"live residency is at the {constants.LIVE_MAX_RESIDENT_ENV} "
                f"cap ({cap} resident games) and no game is evictable "
                "(journal-less games cannot be evicted without losing "
                "history; busy games are never stalled) — retry, close a "
                "game, or raise the cap",
                retry_after_sec=retry_after_sec())
        _set_gauges()


def touch(game) -> None:
    """LRU-bump a resident game (every append/query). Equivalent to
    `admit` but named for the hot path."""
    admit(game)


def forget(game) -> None:
    """Drop a game from the books (close)."""
    with _lock:
        _resident.pop(id(game), None)
        _evicted.pop(id(game), None)
        _set_gauges()


def note_restore(seconds: float) -> None:
    """Record one WAL-restore latency (the retry_after_sec basis and the
    /varz `last_restore_s` field)."""
    with _lock:
        _restore_window.append(float(seconds))
        _totals["restores"] += 1
        _totals["last_restore_s"] = float(seconds)


def _set_gauges() -> None:
    obs_metrics.gauge("live.games_resident").set(len(_resident))
    obs_metrics.gauge("live.games_evicted").set(len(_evicted))


def stats() -> dict:
    """The /varz `live_residency` block (JSON-serializable)."""
    with _lock:
        _prune_dead()
        return {
            "max_resident": max_resident(),
            "resident": len(_resident),
            "evicted": len(_evicted),
            "evictions": _totals["evictions"],
            "restores": _totals["restores"],
            "last_restore_s": round(_totals["last_restore_s"], 6),
        }
