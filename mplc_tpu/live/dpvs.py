"""DPVS-style dynamic coalition pruning for the live contributivity tier.

DPVS-Shapley (arXiv:2410.15093) accelerates federated contribution
evaluation by dynamically pruning low-contribution participants from the
coalition-evaluation schedule: a participant whose recorded updates carry
little information cannot move v(S) measurably, so coalitions that differ
only by such participants need not be evaluated separately. This module
implements that idea against the live tier's resident round history:

  - **Information scores.** Each partner p gets
    `s_p = sum_r |w_h[r, p]| * ||delta_p^r||_2` over the game's recorded
    aggregation rounds — the total weighted parameter motion the partner
    contributed to the grand-coalition trajectory. Zero-weight rounds
    contribute zero; a dropped partner's exactly-zero deltas score 0.
  - **Pruning rule.** With threshold tau in (0, 1], partners with
    `s_p < tau * max_q s_q` are LOW-INFORMATION. A requested coalition S
    is *projected* onto the high-information partners
    (`proj(S) = S minus the low set`); all coalitions sharing a
    projection are served the projection's reconstructed value from ONE
    device evaluation. Pruned partners therefore carry exactly-zero
    marginals everywhere — the DPVS approximation, which is tight
    precisely when the information scores are small.
  - **Exactness-preserving off switch.** tau = 0 (the
    `MPLC_TPU_LIVE_PRUNE_TAU` default) disables pruning entirely: the
    query path never constructs a `PrunedReconstruction` and values are
    bit-identical to the unpruned reconstruction path (equality-tested in
    tests/test_live.py).

Documented deviation from the paper: DPVS prunes during live federated
training rounds using per-round validation signals; here the pruning
signal is derived *post hoc* from the recorded update stream (the only
signal a retrain-free reconstruction game has), and pruning is a
coalition-selection policy over reconstruction evals, not a training-time
participant filter. See doc/documentation.md "Live contributivity tier".
"""

from __future__ import annotations

import numpy as np

from ..obs import metrics as obs_metrics


def info_scores(rounds, partners_count: int) -> np.ndarray:
    """Per-partner information score over `rounds`, a list of
    `(deltas, weights)` pairs with host-array leaves of shape
    `[P, ...]` / `[P]`: `s_p = sum_r |w[r, p]| * ||delta_p^r||_2` (the
    L2 norm taken over all parameter leaves of round r's partner-p
    delta)."""
    import jax

    s = np.zeros(partners_count, float)
    for deltas, weights in rounds:
        sq = np.zeros(partners_count, float)
        for leaf in jax.tree_util.tree_leaves(deltas):
            flat = np.asarray(leaf, float).reshape(partners_count, -1)
            sq += np.sum(flat * flat, axis=1)
        s += np.abs(np.asarray(weights, float)) * np.sqrt(sq)
    return s


def low_information(scores: np.ndarray, tau: float) -> frozenset:
    """The pruned-partner set for threshold `tau`: partners whose score
    falls below `tau * max(scores)`. The max-scoring partner can never be
    pruned (strict inequality), and a degenerate all-zero score vector
    prunes nobody — pruning must never silently empty the game."""
    if tau <= 0 or scores.size == 0:
        return frozenset()
    mx = float(scores.max())
    if mx <= 0:
        return frozenset()
    return frozenset(int(i) for i in np.nonzero(scores < tau * mx)[0])


class PrunedReconstruction:
    """A coalition-selection policy wrapped around a
    `ReconstructionEvaluator`: requested coalitions are projected onto
    the high-information partners and served from the projection's
    evaluated value. Mirrors the evaluator's estimator-facing surface
    (`evaluate` + a `values` memo the permutation sweeps read), so every
    live query method runs against it unchanged."""

    def __init__(self, recon, low: frozenset):
        self.recon = recon
        self.low = low
        self.values: dict[tuple, float] = {(): 0.0}
        # coalitions served from a projected representative instead of
        # their own device evaluation (the DPVS saving, counter-asserted)
        self.pruned = 0

    @property
    def reconstructions(self) -> int:
        return self.recon.reconstructions

    def _project(self, key: tuple) -> tuple:
        return tuple(i for i in key if i not in self.low)

    def evaluate(self, subsets) -> np.ndarray:
        keys = [tuple(sorted(int(i) for i in s)) for s in subsets]
        unique = [k for k in dict.fromkeys(keys) if k not in self.values]
        proj = {k: self._project(k) for k in unique}
        need = [p for p in dict.fromkeys(proj.values()) if p]
        if need:
            self.recon.evaluate(need)
        pruned = 0
        for k in unique:
            p = proj[k]
            if k != p:
                pruned += 1
            self.values[k] = self.recon.values[p] if p else 0.0
        if pruned:
            self.pruned += pruned
            obs_metrics.counter("live.pruned_coalitions").inc(pruned)
        return np.array([self.values[k] for k in keys])
