"""Live contributivity tier: resident incremental games, sub-second
Shapley queries from recorded-round reconstruction, DPVS-style dynamic
coalition pruning, WAL-backed bounded residency (live/residency.py) and
hierarchical/grouped Shapley past the 16-partner exact wall
(live/hierarchy.py). See live/game.py for the full contract."""

from . import residency
from .dpvs import PrunedReconstruction, info_scores, low_information
from .game import (LIVE_METHODS, LiveGame, LiveGameFull, LiveQueryResult,
                   LiveResidencyFull, MAX_EXACT_PARTNERS)
from .hierarchy import (MAX_CLUSTERS, cluster_partners, default_clusters,
                        hierarchical_shapley)

__all__ = ["LIVE_METHODS", "LiveGame", "LiveGameFull", "LiveQueryResult",
           "LiveResidencyFull", "MAX_CLUSTERS", "MAX_EXACT_PARTNERS",
           "PrunedReconstruction", "cluster_partners", "default_clusters",
           "hierarchical_shapley", "info_scores", "low_information",
           "residency"]
