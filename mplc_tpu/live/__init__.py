"""Live contributivity tier: resident incremental games, sub-second
Shapley queries from recorded-round reconstruction, and DPVS-style
dynamic coalition pruning. See live/game.py for the full contract."""

from .dpvs import PrunedReconstruction, info_scores, low_information
from .game import (LIVE_METHODS, LiveGame, LiveGameFull, LiveQueryResult,
                   MAX_EXACT_PARTNERS)

__all__ = ["LIVE_METHODS", "LiveGame", "LiveGameFull", "LiveQueryResult",
           "MAX_EXACT_PARTNERS", "PrunedReconstruction", "info_scores",
           "low_information"]
