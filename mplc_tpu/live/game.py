"""The live contributivity tier: resident incremental games.

Every estimator before this package is batch-shaped — a contributivity
query means "submit a job, run a sweep". A `LiveGame` inverts that: the
tenant's recorded per-partner update history (the `upd_h`/`w_h` stream of
contrib/reconstruct.py) stays RESIDENT, new aggregation rounds are
appended as they happen, and `query(method=...)` answers "what is my
Shapley value *now*" by GTG-style reconstruction against pre-banked AOT
executables — sub-second on the warm path, zero training batches ever
(asserted via the `engine.partner_passes` / `engine.batch` counters in
tests/test_live.py).

Incremental semantics — the round-stamp invalidation rule:

  - `append_round(deltas, weights)` appends one aggregation round
    (per-partner parameter deltas `[P, ...]` + normalized weights `[P]`)
    to the resident history. A round with any non-zero weight is
    INVALIDATING: it advances the game's `round_stamp`, and every
    reconstruction-derived value (the evaluator's memo, cached query
    results) carries the stamp it was computed at and is lazily
    recomputed on the next query. A round whose weights are ALL zero is
    a pass-through for the reconstruction scan (the zero-denominator
    rule in contrib/reconstruct.py) and is NON-invalidating: it is
    journaled and counted resident, but memoized values survive it
    bit-identically — which is what makes repeated queries O(memo)
    regardless of how much history has accumulated.
  - The engine's EXACT memo (`charac_fct_values`, retrained values) is
    never touched by appends: retrained v(S) does not depend on the
    recorded stream, only reconstruction-derived values do.

Durability: with a `journal_path` the game rides the sweep service's
checksummed WAL (service/journal.py — same torn-tail quarantine, same
fsync-before-return contract): one `live_init` record (partners/model
guard + the replay-origin init params) and one `live_round` record per
append. A kill→restart restores the game bit-identically — floats
round-trip exactly through the JSON encoding, so a restored game's
queries equal the killed game's (equality-tested).

Residency: round stacks are RAM unless the process-wide residency
manager (live/residency.py, `MPLC_TPU_LIVE_MAX_RESIDENT`) evicts a cold
journal-backed game down to a stub. The WAL journals every round exactly,
so the next touch restores through the same replay path a restart uses —
eviction is a latency tier, and evict -> restore -> query is
bit-identical to never-evicted (equality-tested in
tests/test_live_residency.py).

Execution: queries run through `ReconstructionEvaluator` — the same
merged slot buckets, device-batch caps, fault ladder and span/event
vocabulary as every other reconstruction — with the program bank
extended to AOT-compile the fused reconstruct+eval program per
(rounds, width) under shared-scope keys, so a second tenant of the same
shape (or the same game after a restart) executes from the bank with
zero compiles. DPVS-style pruning (live/dpvs.py,
`MPLC_TPU_LIVE_PRUNE_TAU`) optionally collapses coalitions that differ
only by low-information partners onto one evaluated representative;
tau = 0 (default) is the exactness-preserving off switch.
"""

from __future__ import annotations

import logging
import threading
import time

import numpy as np

import jax

from .. import constants
from ..contrib.reconstruct import RecordedRun, _check_not_2d
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..service.journal import SweepJournal
from . import residency
from .dpvs import PrunedReconstruction, info_scores, low_information

logger = logging.getLogger("mplc_tpu")

#: Methods `LiveGame.query` answers ("Shapley values" aliases "exact").
LIVE_METHODS = ("exact", "hierarchical", "GTG-Shapley", "SVARM")

# exact queries materialize the 2^P host-side table (shapley weights over
# every bitmask) — past this partner count the host cost alone breaks the
# sub-second contract. Past the wall, "hierarchical" (live/hierarchy.py)
# reuses this whole exact path over <= 16 CLUSTERS of partners, and the
# sampling methods have no bound at all — exact-per-partner is capped,
# large games are not refused.
MAX_EXACT_PARTNERS = 16


class LiveGameFull(RuntimeError):
    """append_round past the resident-round cap
    (`MPLC_TPU_LIVE_MAX_ROUNDS`): the game refuses to grow its
    reconstruction depth and journal without bound. Start a new game (or
    raise the cap) — silently evicting history would change v(S).

    Carries a `retry_after_sec` backoff hint (0.0 = no estimate), the
    `ServiceOverloaded` convention, so streaming clients back off
    instead of hammering the ingestion endpoint."""

    def __init__(self, msg, retry_after_sec: float = 0.0):
        super().__init__(msg)
        self.retry_after_sec = float(retry_after_sec)


class LiveResidencyFull(LiveGameFull):
    """Residency admission refused: the process is at the
    `MPLC_TPU_LIVE_MAX_RESIDENT` cap and no resident game is evictable
    (journal-less or busy). The `retry_after_sec` hint is the p50 of
    recent WAL-restore latencies (live/residency.py)."""


class LiveQueryResult:
    """One answered live query: the scores, the round-stamp they were
    computed at (`stamp` — a result whose stamp trails the game's
    `round_stamp` is stale and is never served), and the query's cost
    accounting."""

    __slots__ = ("method", "scores", "stamp", "rounds", "seconds",
                 "evaluations", "pruned_coalitions", "prune_tau",
                 "low_info", "trust", "plan")

    def __init__(self, method, scores, stamp, rounds, seconds, evaluations,
                 pruned_coalitions, prune_tau, low_info, trust, plan=None):
        self.method = method
        self.scores = np.asarray(scores)
        self.stamp = int(stamp)
        self.rounds = int(rounds)
        self.seconds = float(seconds)
        self.evaluations = int(evaluations)
        self.pruned_coalitions = int(pruned_coalitions)
        self.prune_tau = float(prune_tau)
        self.low_info = tuple(low_info)
        self.trust = trust
        # the adaptive planner's resolved QueryPlan for method="auto"
        # queries (None for direct method queries): carries the concrete
        # method/kwargs a replay must run
        self.plan = plan

    def describe(self) -> dict:
        d = {"method": self.method, "stamp": self.stamp,
             "rounds": self.rounds, "seconds": round(self.seconds, 6),
             "evaluations": self.evaluations,
             "pruned_coalitions": self.pruned_coalitions,
             "prune_tau": self.prune_tau,
             "scores": [float(x) for x in self.scores]}
        if self.plan is not None:
            d["plan"] = self.plan.describe()
        return d


def _encode_tree(tree) -> list:
    """JSON-encode a pytree's leaves as [[shape, dtype, flat-values]...]
    (floats round-trip exactly through json's repr-based serialization —
    the same property the service WAL's v(S) records rest on)."""
    out = []
    for leaf in jax.tree_util.tree_leaves(tree):
        a = np.asarray(leaf)
        out.append([list(a.shape), str(a.dtype), a.ravel().tolist()])
    return out


def _decode_tree(doc: list, treedef):
    leaves = [np.asarray(vals, dtype=np.dtype(dt)).reshape([int(d) for d in shape])
              for shape, dt, vals in doc]
    return jax.tree_util.tree_unflatten(treedef, leaves)


class LiveGame:
    """One tenant's resident incremental contributivity game."""

    def __init__(self, scenario, tenant: str = "tenant0",
                 journal_path=None, max_rounds: "int | None" = None,
                 engine=None):
        if engine is None:
            engine = getattr(scenario, "_charac_engine", None)
        if engine is None:
            from ..contrib.bank import ProgramBank, bank_enabled
            from ..contrib.engine import CharacteristicEngine
            engine = CharacteristicEngine(scenario)
            if bank_enabled():
                # shared-scope program keys (the sweep service's mode):
                # a second tenant of the same SHAPE — or this game after
                # a restart — is served the same banked executables
                engine.program_bank = ProgramBank(engine, shared=True)
            scenario._charac_engine = engine
        elif getattr(scenario, "_charac_engine", None) is None:
            scenario._charac_engine = engine
        _check_not_2d(engine)
        self.engine = engine
        self.scenario = scenario
        self.tenant = str(tenant)
        self.max_rounds = (int(max_rounds) if max_rounds is not None
                           else constants._env_positive_int(
                               constants.LIVE_MAX_ROUNDS_ENV, 4096))
        # the replay origin: reconstruction replays rounds from exactly
        # these params. Derived from the engine's grand-coalition rng —
        # the same stream record_updates initializes from — unless a
        # journal restore below supplies the recorded origin.
        self._init_params = self._derive_init_params()
        self._treedef = jax.tree_util.tree_structure(self._init_params)
        # resident history: [(deltas pytree of np [P, ...], weights np [P])]
        self._rounds: list = []
        # advanced by every INVALIDATING append; reconstruction-derived
        # values carry the stamp they were computed at
        self.round_stamp = 0
        self.queries = 0
        self._recon = None
        self._recon_stamp = -1
        self._results: dict = {}
        self._info_cache = None  # (stamp, rounds_resident) -> scores
        # residency state: an evicted game keeps only this stub —
        # (round_stamp, rounds) at eviction, integrity-checked on restore
        self._evicted = False
        self._evicted_state = (0, 0)
        self.last_restore_s = 0.0
        # one game = one serialized surface: the service's worker POOL
        # can land two live-query quanta (or an append racing a query)
        # for the same tenant on different workers, and the evaluator /
        # memo / stamp trio must move atomically
        self._lock = threading.RLock()

        self._journal = None
        if journal_path is not None:
            records, _torn = SweepJournal.replay(journal_path)
            restored = self._restore(records)
            self._journal = SweepJournal(journal_path)
            if not restored:
                self._journal.append({
                    "type": "live_init", "tenant": self.tenant,
                    "partners_count": int(engine.partners_count),
                    "model": getattr(engine.model, "name", "?"),
                    "params": _encode_tree(self._init_params)})
        # residency admission: past the MPLC_TPU_LIVE_MAX_RESIDENT cap
        # this evicts the coldest journal-backed game — or refuses THIS
        # game (LiveResidencyFull) when nothing is evictable
        try:
            residency.admit(self)
        except BaseException:
            self.close()
            raise
        self._set_gauges()

    # -- construction helpers -------------------------------------------

    def _derive_init_params(self):
        eng = self.engine
        full = tuple(range(eng.partners_count))
        eff = eng._effective_subset(full)
        rng = eng._coalition_rng(eff if eff else full)
        trainer = eng.multi_pipe.trainer
        params = trainer.init_state(rng, eng.partners_count).params
        return jax.tree_util.tree_map(
            lambda l: np.asarray(jax.device_get(l)), params)

    @classmethod
    def from_recording(cls, scenario, **kw) -> "LiveGame":
        """Seed a live game from ONE grand-coalition recording run
        (contrib/reconstruct.record_updates): the recorded rounds become
        the game's initial resident history, after which `append_round`
        extends it incrementally. The recording is the only training the
        game ever pays."""
        game = cls(scenario, **kw)
        if game.rounds_resident:
            # a journal restore already holds history: re-recording would
            # double every round
            return game
        from ..contrib.reconstruct import record_updates
        rec = record_updates(game.engine)
        deltas = jax.tree_util.tree_map(
            lambda l: np.asarray(jax.device_get(l)), rec.deltas)
        weights = np.asarray(jax.device_get(rec.weights))
        with game._lock:
            # one durability point for the whole recording: a realistic
            # run records epochs x minibatches rounds, and seeding must
            # not pay one journal fsync per round
            game._append_rounds([
                (jax.tree_util.tree_map(lambda l, _r=r: l[_r], deltas),
                 weights[r])
                for r in range(rec.rounds)])
        return game

    def _restore(self, records) -> bool:
        """Replay a journal's live records into this game. Returns True
        when a `live_init` record was found (the journal already owns the
        game's identity)."""
        inited = False
        rounds = 0
        for rec in records:
            kind = rec.get("type")
            if kind == "live_init":
                jp = rec.get("partners_count")
                if jp is not None and int(jp) != self.engine.partners_count:
                    raise ValueError(
                        f"live journal was recorded for {jp} partners but "
                        f"this game has {self.engine.partners_count} — "
                        "refusing to restore a different game's history")
                jm = rec.get("model")
                ours = getattr(self.engine.model, "name", "?")
                if jm is not None and jm != ours:
                    raise ValueError(
                        f"live journal was recorded for model {jm!r} but "
                        f"this game trains {ours!r} — refusing to restore "
                        "a different game's history (same-shape "
                        "architectures would silently answer the wrong "
                        "game)")
                self._init_params = _decode_tree(rec["params"], self._treedef)
                inited = True
            elif kind == "live_round":
                deltas = _decode_tree(rec["deltas"], self._treedef)
                weights = np.asarray(rec["weights"], np.float32)
                self._rounds.append((deltas, weights))
                if np.any(weights != 0):
                    self.round_stamp += 1
                rounds += 1
        if rounds:
            obs_metrics.counter("live.games_recovered").inc()
            obs_trace.event("live.recover", tenant=self.tenant,
                            rounds=rounds, stamp=self.round_stamp)
        return inited

    # -- the incremental surface ----------------------------------------

    @property
    def rounds_resident(self) -> int:
        return len(self._rounds)

    def round_history(self) -> list:
        """The resident `(deltas, weights)` rounds, in append order
        (host arrays; the bench's append-replay loop reads this).
        Restores an evicted game first."""
        with self._lock:
            self._ensure_resident()
            return list(self._rounds)

    def _set_gauges(self) -> None:
        obs_metrics.gauge("live.rounds_resident",
                          tenant=self.tenant).set(len(self._rounds))

    # -- residency (live/residency.py calls in; queries call out) --------

    @property
    def resident(self) -> bool:
        return not self._evicted

    def evict(self) -> bool:
        """Evict this game's round stack (and every derived evaluator/
        memo) down to a stub. Only journal-backed games are evictable —
        the WAL holds every round exactly, so the next touch restores
        bit-identically. Returns False (still resident) without a
        journal. Normally driven by the residency manager's LRU, public
        for tests and operators."""
        with self._lock:
            return self._evict_locked()

    def _evict_locked(self) -> bool:
        if self._journal is None or self._evicted:
            return False
        rounds = len(self._rounds)
        self._evicted_state = (self.round_stamp, rounds)
        self._rounds = []
        self._recon = None
        self._recon_stamp = -1
        self._results = {}
        self._info_cache = None
        self._evicted = True
        residency.note_evicted(self)
        obs_metrics.counter("live.evictions").inc()
        obs_trace.event("live.evict", tenant=self.tenant, rounds=rounds,
                        stamp=self.round_stamp)
        self._set_gauges()
        return True

    def _ensure_resident(self) -> None:
        """Restore an evicted game's round stack from its WAL (the same
        `live.recover` replay path a restart uses) before any read or
        append; LRU-bump otherwise. Caller holds the lock."""
        if not self._evicted:
            residency.touch(self)
            return
        # admission first: restoring must not blow the cap, and a refusal
        # (LiveResidencyFull, with backoff hint) leaves the stub intact
        residency.admit(self)
        t0 = time.perf_counter()
        records, _torn = SweepJournal.replay(self._journal.path)
        saved_stamp, saved_rounds = self._evicted_state
        self.round_stamp = 0
        self._restore(records)
        if (self.round_stamp, len(self._rounds)) != (saved_stamp,
                                                     saved_rounds):
            raise RuntimeError(
                f"live game {self.tenant!r} restored to "
                f"(stamp={self.round_stamp}, rounds={len(self._rounds)}) "
                f"but was evicted at (stamp={saved_stamp}, "
                f"rounds={saved_rounds}) — the WAL and the stub disagree")
        self._evicted = False
        self.last_restore_s = time.perf_counter() - t0
        residency.note_restore(self.last_restore_s)
        obs_metrics.counter("live.restores").inc()
        obs_trace.event("live.restore", tenant=self.tenant,
                        rounds=len(self._rounds), stamp=self.round_stamp,
                        restore_s=round(self.last_restore_s, 6))
        self._set_gauges()

    def append_round(self, deltas, weights) -> int:
        """Append one aggregation round's per-partner deltas (`[P, ...]`
        pytree, same structure as the model params) and normalized
        weights (`[P]`). Returns the game's round-stamp after the append
        — unchanged for a non-invalidating (all-zero-weight) round, so
        memoized values provably survive it. The round is journaled
        (durably, fsync'd) before any in-memory state changes."""
        with self._lock:
            return self._append_rounds([(deltas, weights)])

    def _normalize_round(self, deltas, weights):
        """Validate one round's shapes and pull it to host arrays."""
        P = self.engine.partners_count
        w = np.asarray(jax.device_get(weights), np.float32).reshape(P)
        d = jax.tree_util.tree_map(
            lambda l: np.asarray(jax.device_get(l)), deltas)
        if jax.tree_util.tree_structure(d) != self._treedef:
            raise ValueError(
                "append_round deltas pytree does not match the model's "
                "parameter structure")
        for leaf, ref in zip(jax.tree_util.tree_leaves(d),
                             jax.tree_util.tree_leaves(self._init_params)):
            if leaf.shape != (P,) + ref.shape:
                raise ValueError(
                    f"append_round delta leaf has shape {leaf.shape}, "
                    f"expected {(P,) + ref.shape} (a [partners, ...] stack "
                    "of per-partner parameter deltas)")
        return d, w

    def _append_rounds(self, rounds) -> int:
        """Append a batch of rounds with ONE journal durability point
        (`append_many` — from_recording seeds epochs x minibatches rounds
        and must not pay one fsync per round). Caller holds the lock."""
        self._ensure_resident()
        if len(self._rounds) + len(rounds) > self.max_rounds:
            raise LiveGameFull(
                f"live game for tenant {self.tenant!r} holds "
                f"{len(self._rounds)} resident rounds and was asked for "
                f"{len(rounds)} more — the {constants.LIVE_MAX_ROUNDS_ENV} "
                f"cap ({self.max_rounds}); evicting history would change "
                "v(S), so start a new game or raise the cap")
        normalized = [self._normalize_round(d, w) for d, w in rounds]
        if self._journal is not None:
            self._journal.append_many([
                {"type": "live_round", "tenant": self.tenant,
                 "seq": len(self._rounds) + 1 + i,
                 "weights": [float(x) for x in w],
                 "deltas": _encode_tree(d)}
                for i, (d, w) in enumerate(normalized)])
        for d, w in normalized:
            self._rounds.append((d, w))
            invalidating = bool(np.any(w != 0))
            if invalidating:
                self.round_stamp += 1
            obs_metrics.counter("live.rounds_appended").inc()
            obs_trace.event("live.append", tenant=self.tenant,
                            seq=len(self._rounds), stamp=self.round_stamp,
                            invalidating=invalidating)
        self._set_gauges()
        return self.round_stamp

    # -- reconstruction plumbing ----------------------------------------

    def _build_recorded(self) -> RecordedRun:
        """The resident history as a `RecordedRun`: zero-weight rounds
        are excluded from the stack (the scan would pass through them
        unchanged), so a restored game and the live game that skipped
        them reconstruct bit-identically."""
        import jax.numpy as jnp
        P = self.engine.partners_count
        live = [(d, w) for d, w in self._rounds if np.any(w != 0)]
        if live:
            deltas = jax.tree_util.tree_map(
                lambda *leaves: jnp.asarray(np.stack(leaves)),
                *[d for d, _ in live])
            weights = jnp.asarray(np.stack([w for _, w in live]))
        else:
            deltas = jax.tree_util.tree_map(
                lambda l: jnp.zeros((0, P) + l.shape, l.dtype),
                self._init_params)
            weights = jnp.zeros((0, P), np.float32)
        init = jax.tree_util.tree_map(jnp.asarray, self._init_params)
        mem = int(sum(int(np.prod(l.shape)) * l.dtype.itemsize
                      for l in jax.tree_util.tree_leaves(deltas))
                  + weights.size * weights.dtype.itemsize)
        return RecordedRun(init_params=init, deltas=deltas, weights=weights,
                           rounds=len(live), partners_count=P,
                           epochs_done=0, training_passes=0,
                           memory_bytes=mem)

    def _evaluator(self):
        """The game's (round-stamped) reconstruction evaluator. Stale
        stamps swap the recorded stream in place — the memo is derived
        from the old stream and dropped, while the evaluator's jitted
        program cache (and the AOT bank) survives."""
        from ..contrib.reconstruct import ReconstructionEvaluator
        if self._recon is None:
            self._recon = ReconstructionEvaluator(
                self.engine, recorded=self._build_recorded())
            self._recon.use_bank = True
            self._recon_stamp = self.round_stamp
        elif self._recon_stamp != self.round_stamp:
            self._recon.reset_recorded(self._build_recorded())
            self._recon_stamp = self.round_stamp
        return self._recon

    def _info_scores(self) -> np.ndarray:
        key = (self.round_stamp, len(self._rounds))
        if self._info_cache is None or self._info_cache[0] != key:
            self._info_cache = (key, info_scores(
                self._rounds, self.engine.partners_count))
        return self._info_cache[1]

    # -- queries ---------------------------------------------------------

    def query(self, method: str = "GTG-Shapley", prune: "float | None" = None,
              accuracy_target: "float | None" = None,
              deadline_sec: "float | None" = None,
              **method_kw) -> LiveQueryResult:
        """Answer a contributivity query from the resident game.

        `method`: "exact" (full reconstructed powerset + exact Shapley;
        partner counts <= 16), "hierarchical" (DPVS-clustered grouped
        Shapley for larger games — exact over <= 16 clusters, split
        within; `clusters`/`cluster_tau` kwargs, live/hierarchy.py),
        "GTG-Shapley" or "SVARM" (their usual
        kwargs pass through), or "auto" — the adaptive planner
        (contrib/planner.py) resolves (game size, `accuracy_target`,
        `deadline_sec`) to a concrete method + pruning tau, the plan
        rides the result (`result.plan`) and a `live.plan` event, and
        the plan ALONE determines the query (its prune_tau wins over the
        env default) so a journaled plan replays bit-identically.
        `prune` is the DPVS threshold tau (None = the
        `MPLC_TPU_LIVE_PRUNE_TAU` env default, 0 = off). Results are
        memoized per (method, tau, precision, kwargs) and served without
        any device work while the round-stamp is unchanged; a stale
        result is never served. Queries (and appends) on one game are
        serialized by the game's lock — the service's worker pool can
        schedule two of a tenant's quanta concurrently."""
        with self._lock:
            return self._query_locked(method, prune, method_kw,
                                      accuracy_target, deadline_sec)

    def _query_locked(self, method: str, prune: "float | None",
                      method_kw: dict,
                      accuracy_target: "float | None" = None,
                      deadline_sec: "float | None" = None
                      ) -> LiveQueryResult:
        self._ensure_resident()
        if method == "Shapley values":
            method = "exact"
        plan = None
        if method == "auto":
            from ..contrib.planner import estimate_eval_seconds, plan_query
            eval_sec, basis = estimate_eval_seconds(self.engine)
            plan = plan_query(self.engine.partners_count, accuracy_target,
                              deadline_sec, eval_sec=eval_sec,
                              cost_basis=basis, live=True)
            method = plan.method
            # the plan fully determines the query (replayability): its
            # tau wins even when 0 — an env-default tau must not leak
            # into an auto query the journaled plan doesn't mention
            prune = plan.prune_tau
            method_kw = {**plan.method_kw, **method_kw}
            obs_trace.event("live.plan", tenant=self.tenant,
                            **plan.describe())
        if method not in LIVE_METHODS:
            raise ValueError(
                f"unknown live query method {method!r} (expected one of "
                f"{LIVE_METHODS})")
        # tau lives in [0, 1]: past 1 even the max-scoring partner would
        # prune and every query would silently return all-zero scores.
        # An explicit argument fails fast; the env knob degrades with a
        # warning (the same typo'd-knob contract as every other knob)
        if prune is None:
            tau = constants._env_nonneg_float(
                constants.LIVE_PRUNE_TAU_ENV, 0.0)
            if tau > 1.0:
                import warnings
                warnings.warn(
                    f"{constants.LIVE_PRUNE_TAU_ENV}={tau} is outside "
                    "[0, 1]; pruning disabled for this query",
                    stacklevel=3)
                tau = 0.0
        else:
            tau = float(prune)
            if not 0.0 <= tau <= 1.0:
                raise ValueError(
                    f"prune tau must be in [0, 1], got {tau}")
        n = self.engine.partners_count
        # the precision mode keys the memo: the engine's mode is frozen,
        # but a journal-restored game can be re-opened under a different
        # MPLC_TPU_PRECISION — a bf16 answer must never serve an fp32
        # query (ISSUE 17's memo-keying fix, same rule as the bank key)
        precision = getattr(self.engine._multi_cfg, "precision", "fp32")
        key = (method, tau, precision, tuple(sorted(method_kw.items())))
        span = obs_trace.start_span(
            "live.query", tenant=self.tenant, method=method,
            rounds=self.rounds_resident, stamp=self.round_stamp,
            prune_tau=tau)
        try:
            cached = self._results.get(key)
            if cached is not None and cached.stamp == self.round_stamp:
                if plan is not None and cached.plan is None:
                    # an auto query memo-hitting an earlier direct query
                    # of the same concrete (method, tau, kwargs): the
                    # plan describes exactly this result — attach it
                    cached.plan = plan
                obs_metrics.counter("live.queries").inc()
                obs_metrics.counter("live.query_memo_hits").inc()
                span.attrs.update(memo_hit=True, evaluations=0, pruned=0)
                span.end()
                obs_metrics.histogram(
                    "live.query_sec",
                    tenant=self.tenant).observe(span.duration)
                return cached
            recon = self._evaluator()
            before = recon.reconstructions
            low: frozenset = frozenset()
            ev = recon
            if tau > 0:
                low = low_information(self._info_scores(), tau)
                if low:
                    ev = PrunedReconstruction(recon, low)
            trust = None
            t0 = time.perf_counter()
            if method == "exact":
                if n > MAX_EXACT_PARTNERS:
                    raise ValueError(
                        f"live exact queries are limited to "
                        f"{MAX_EXACT_PARTNERS} partners (the 2^P host "
                        f"table; this game has {n}) — use hierarchical, "
                        "GTG-Shapley or SVARM")
                from ..contrib.shapley import (powerset_order,
                                               shapley_from_characteristic)
                ev.evaluate(powerset_order(n))
                scores = np.asarray(
                    shapley_from_characteristic(n, ev.values))
            elif method == "hierarchical":
                from .hierarchy import hierarchical_shapley
                scores, hdetail = hierarchical_shapley(
                    ev, n, self._info_scores(), **method_kw)
                span.attrs.update(
                    clusters=len(hdetail["clusters"]),
                    proportional_splits=hdetail["proportional_splits"])
            else:
                from ..contrib.contributivity import Contributivity
                eng = self.engine
                prev = getattr(eng, "_reconstruction", None)
                eng._reconstruction = ev
                try:
                    c = Contributivity(self.scenario)
                    if method == "GTG-Shapley":
                        c.GTG_Shapley(**method_kw)
                    else:
                        c.SVARM(**method_kw)
                finally:
                    eng._reconstruction = prev
                scores = np.asarray(c.contributivity_scores)
                trust = c.trust
            seconds = time.perf_counter() - t0
            evals = recon.reconstructions - before
            pruned = ev.pruned if isinstance(ev, PrunedReconstruction) else 0
            result = LiveQueryResult(
                method=method, scores=scores, stamp=self.round_stamp,
                rounds=self.rounds_resident, seconds=seconds,
                evaluations=evals, pruned_coalitions=pruned, prune_tau=tau,
                low_info=sorted(low), trust=trust, plan=plan)
            self._results[key] = result
            self.queries += 1
            obs_metrics.counter("live.queries").inc()
            obs_metrics.counter("live.coalition_evaluations").inc(evals)
            span.attrs.update(memo_hit=False, evaluations=evals,
                              pruned=pruned, low_info=len(low))
            span.end()
            obs_metrics.histogram(
                "live.query_sec", tenant=self.tenant).observe(span.duration)
            return result
        except BaseException:
            span.cancel()
            raise

    # -- observability / lifecycle --------------------------------------

    def describe(self) -> dict:
        """The game's /varz row (JSON-serializable)."""
        return {
            "tenant": self.tenant,
            "rounds_resident": self.rounds_resident,
            "round_stamp": self.round_stamp,
            "queries": self.queries,
            "results_cached": len(self._results),
            "max_rounds": self.max_rounds,
            "journal": self._journal.path if self._journal else None,
            # residency state: an observability read must never trigger
            # a restore, so this reports the stub as-is
            "resident": self.resident,
            "last_restore_s": round(self.last_restore_s, 6),
        }

    def close(self) -> None:
        residency.forget(self)
        if self._journal is not None:
            self._journal.close()
