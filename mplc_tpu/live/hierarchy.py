"""Hierarchical/grouped Shapley: live queries past the 16-partner wall.

Exact live queries materialize the 2^P host table, so `live/game.py`
caps them at 16 partners. A 100-partner consortium still wants
exact-shaped answers, and the DPVS info scores the live tier already
computes (live/dpvs.py) provide exactly the per-partner signal needed to
GROUP partners: cluster by info score, evaluate coalitions of CLUSTERS
exactly (cluster count <= 16 reuses the whole exact path — the same
batched evaluator, merged slot buckets and AOT program bank), then split
each cluster's macro Shapley value among its members:

  - clusters of one: the member inherits the macro value (exact).
  - clusters up to `INTRA_EXACT_MAX` members: an exact Shapley split of
    the subgame restricted to the cluster, shifted by the per-member
    share of the synergy residual (the macro value minus the subgame
    sum) so efficiency is preserved exactly:
    `phi_i = psi_i + (PHI_C - sum(psi)) / |C|`.
  - larger clusters: split proportionally to within-cluster info scores
    (equal shares when all scores are zero).

Efficiency holds by construction at every rung — the macro level is
exact Shapley (sums to v(grand)) and both splits conserve the cluster's
macro value — so `sum(scores) == v(grand coalition)` up to float
roundoff regardless of cluster count.

Documented deviation: grouped/stratified Shapley (the same decomposition
trick GTG-Shapley, arXiv 2109.02053, plays along the ROUND axis) is
exact only when partners interact solely through their cluster — the
within-cluster split ignores cross-cluster synergies below the macro
level. The clustering keys on DPVS info scores precisely so that
same-signal partners (whose cross terms matter most) land in the same
cluster, and the quality floor is pinned as a Kendall-tau bound against
the unpruned sampled reference in tests/test_live_hierarchy.py.
"""

from __future__ import annotations

import math
from itertools import combinations

import numpy as np

from .. import constants
from .dpvs import low_information

#: intra-cluster exact-split ceiling: up to this many members, a cluster
#: is split by an exact subgame Shapley (2^size extra evaluations);
#: larger clusters fall back to the info-score-proportional split
INTRA_EXACT_MAX = 12

#: coalitions of clusters ride the exact 2^k host table, so the cluster
#: count inherits the exact wall
MAX_CLUSTERS = 16


def default_clusters(partners_count: int) -> int:
    """The auto cluster count: ceil(sqrt(P)) clamped to [2, 16] — keeps
    both the macro powerset (2^k) and the intra subgames (~2^(P/k))
    small for the partner counts the live tier serves."""
    p = max(1, int(partners_count))
    return max(2, min(MAX_CLUSTERS, math.isqrt(p - 1) + 1))


def resolve_clusters(partners_count: int,
                     clusters: "int | None" = None) -> int:
    """The effective cluster count: explicit argument, else the
    `MPLC_TPU_LIVE_CLUSTERS` knob, else the auto heuristic. An explicit
    out-of-range argument fails fast (the usual knob contract)."""
    if clusters is None:
        k = constants._env_nonneg_int(constants.LIVE_CLUSTERS_ENV, 0)
        if k > MAX_CLUSTERS:
            import warnings
            warnings.warn(
                f"{constants.LIVE_CLUSTERS_ENV}={k} exceeds the exact "
                f"wall; clamped to {MAX_CLUSTERS}", stacklevel=3)
            k = MAX_CLUSTERS
        clusters = k if k else default_clusters(partners_count)
    k = int(clusters)
    if not 1 <= k <= MAX_CLUSTERS:
        raise ValueError(
            f"hierarchical cluster count must be in [1, {MAX_CLUSTERS}] "
            f"(coalitions of clusters ride the exact 2^k table), got {k}")
    return k


def resolve_cluster_tau(cluster_tau: "float | None" = None) -> float:
    """The effective tail threshold: explicit argument (fail-fast on
    out-of-range), else the `MPLC_TPU_LIVE_CLUSTER_TAU` knob (degrades
    to 0 with a warning — the typo'd-knob contract)."""
    if cluster_tau is None:
        tau = constants._env_nonneg_float(
            constants.LIVE_CLUSTER_TAU_ENV, 0.0)
        if tau > 1.0:
            import warnings
            warnings.warn(
                f"{constants.LIVE_CLUSTER_TAU_ENV}={tau} is outside "
                "[0, 1]; tail clustering disabled", stacklevel=3)
            tau = 0.0
        return tau
    tau = float(cluster_tau)
    if not 0.0 <= tau <= 1.0:
        raise ValueError(f"cluster_tau must be in [0, 1], got {tau}")
    return tau


def cluster_partners(scores, clusters: int, tau: float = 0.0) -> tuple:
    """Deterministic score-balanced clustering: partners ordered by
    descending DPVS info score (index-tiebroken) are chopped into
    `clusters` contiguous near-equal chunks, so same-signal partners —
    whose cross-cluster synergies the split would otherwise lose — share
    a cluster. With `tau` > 0, partners scoring below tau x max
    (`dpvs.low_information`; the max scorer never qualifies) are pulled
    into ONE shared tail cluster appended last. Returns a tuple of
    clusters, each a sorted tuple of partner indices."""
    scores = np.asarray(scores, float)
    P = int(scores.size)
    if P == 0:
        return ()
    k = int(clusters)
    if not 1 <= k <= MAX_CLUSTERS:
        raise ValueError(
            f"cluster count must be in [1, {MAX_CLUSTERS}], got {k}")
    tail = tuple(sorted(low_information(scores, tau))) if tau > 0 else ()
    core = sorted((p for p in range(P) if p not in tail),
                  key=lambda p: (-scores[p], p))
    out = []
    if core:
        k_core = max(1, min(k - (1 if tail else 0), len(core)))
        base, extra = divmod(len(core), k_core)
        start = 0
        for j in range(k_core):
            size = base + (1 if j < extra else 0)
            out.append(tuple(sorted(core[start:start + size])))
            start += size
    if tail:
        out.append(tail)
    return tuple(out)


def estimate_evaluations(partners_count: int, clusters: int) -> int:
    """The planner's cost model for a hierarchical query: the macro
    cluster powerset plus every exact intra split, assuming near-equal
    chunks (info scores — and any tau tail — are unknown at plan
    time)."""
    n = int(partners_count)
    k = max(1, min(int(clusters), n))
    total = (1 << k) - 1
    base, extra = divmod(n, k)
    for j in range(k):
        size = base + (1 if j < extra else 0)
        if 1 < size <= INTRA_EXACT_MAX:
            total += (1 << size) - 1
    return total


def hierarchical_shapley(ev, partners_count: int, info,
                         clusters: "int | None" = None,
                         cluster_tau: "float | None" = None
                         ) -> "tuple[np.ndarray, dict]":
    """Grouped Shapley against evaluator `ev` (a
    `ReconstructionEvaluator` or `PrunedReconstruction` — anything with
    the batched `evaluate(subsets) -> values` surface). `info` is the
    game's per-partner DPVS score vector. Returns `(scores, detail)`
    with `detail` JSON-ready for spans/tests. Fully deterministic given
    (ev, info, clusters, cluster_tau) — a journaled plan's frozen kwargs
    replay bit-identically."""
    from ..contrib.shapley import shapley_from_characteristic

    n = int(partners_count)
    info = np.asarray(info, float)
    k = resolve_clusters(n, clusters)
    tau = resolve_cluster_tau(cluster_tau)
    groups = cluster_partners(info, k, tau)
    m = len(groups)

    # every coalition the query needs, evaluated in ONE batched call:
    # cluster unions for the macro game, member powersets for the exact
    # intra splits (full-cluster sets overlap the singleton unions —
    # dict.fromkeys dedups, the evaluator memo would anyway)
    union_of = {}
    for size in range(1, m + 1):
        for T in combinations(range(m), size):
            union_of[T] = tuple(sorted(
                p for j in T for p in groups[j]))
    intra_of = {}
    for j, C in enumerate(groups):
        if 1 < len(C) <= INTRA_EXACT_MAX:
            intra_of[j] = [tuple(c)
                           for s in range(1, len(C) + 1)
                           for c in combinations(C, s)]
    todo = list(dict.fromkeys(
        list(union_of.values())
        + [s for subs in intra_of.values() for s in subs]))
    vals = ev.evaluate(todo)
    v = {s: float(x) for s, x in zip(todo, vals)}

    macro_sv = shapley_from_characteristic(
        m, {T: v[members] for T, members in union_of.items()})

    scores = np.zeros(n)
    exact_splits = proportional_splits = 0
    for j, C in enumerate(groups):
        phi = float(macro_sv[j])
        size = len(C)
        if size == 1:
            scores[C[0]] = phi
        elif j in intra_of:
            sub = {S: v[tuple(C[i] for i in S)]
                   for s in range(1, size + 1)
                   for S in combinations(range(size), s)}
            psi = shapley_from_characteristic(size, sub)
            residual = (phi - float(psi.sum())) / size
            for i, p in enumerate(C):
                scores[p] = float(psi[i]) + residual
            exact_splits += 1
        else:
            w = info[list(C)]
            tot = float(w.sum())
            share = w / tot if tot > 0 else np.full(size, 1.0 / size)
            for i, p in enumerate(C):
                scores[p] = phi * float(share[i])
            proportional_splits += 1

    detail = {
        "clusters": [list(c) for c in groups],
        "cluster_tau": tau,
        "macro_coalitions": (1 << m) - 1,
        "coalitions_evaluated": len(todo),
        "exact_splits": exact_splits,
        "proportional_splits": proportional_splits,
    }
    return scores, detail
