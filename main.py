#!/usr/bin/env python
"""CLI experiment driver: YAML grid -> scenarios -> repeats -> results.csv.

Same contract as the reference /root/reference/main.py: `python main.py -f
config.yml` expands every list-valued parameter into a scenario grid,
validates every scenario with a dry run before any training, then runs
n_repeats x scenarios and appends each scenario's `to_dataframe()` rows to
<experiment>/results.csv.
"""

import os
import sys

from mplc_tpu import utils
from mplc_tpu.scenario import Scenario
from mplc_tpu.utils import parse_command_line_arguments

DEFAULT_CONFIG_FILE = "./config.yml"


def validate_scenario_list(scenario_params_list, experiment_path):
    """Dry-run every scenario (reference main.py:92-111)."""
    logger = utils.logger
    logger.debug("Starting to validate scenarios")
    for scenario_id, scenario_params in enumerate(scenario_params_list):
        current_scenario = Scenario(**scenario_params,
                                    experiment_path=experiment_path,
                                    is_dry_run=True)
        current_scenario.instantiate_scenario_partners()
        if current_scenario.samples_split_type == "basic":
            current_scenario.split_data(is_logging_enabled=False)
        elif current_scenario.samples_split_type == "advanced":
            current_scenario.split_data_advanced(is_logging_enabled=False)
    logger.debug("All scenarios have been validated")


def main(argv=None):
    """Top-level error capture (reference wraps main in @logger.catch,
    main.py:21): any crash in a multi-hour grid is logged WITH traceback to
    the experiment folder's log files before the process exits nonzero."""
    try:
        return _main(argv)
    except SystemExit:
        raise
    except BaseException:
        utils.logger.exception("Experiment run crashed:")
        return 1


def _main(argv=None):
    args = parse_command_line_arguments(argv)
    logger = utils.init_logger(debug=args.verbose)

    config_file = args.file or DEFAULT_CONFIG_FILE
    logger.info(f"Using config file: {config_file}")
    # Multi-host farm-out: the grid axis shares nothing between scenarios,
    # so host I of N simply owns slice I::N (global scenario ids preserved,
    # per-shard results file in ONE shared deterministic folder —
    # concatenate when all hosts finish). argparse already validated the
    # spec, before any filesystem side effect.
    shard = args.grid_shard
    config = utils.get_config_from_file(config_file, shard=shard)

    scenario_params_list = utils.get_scenario_params_list(
        config["scenario_params_list"])
    experiment_path = config["experiment_path"]
    n_repeats = config["n_repeats"]

    indexed_scenarios = list(enumerate(scenario_params_list))
    results_name = "results.csv"
    if shard is not None:
        shard_i, shard_n = shard
        indexed_scenarios = indexed_scenarios[shard_i::shard_n]
        results_name = f"results_shard{shard_i}.csv"
        logger.info(f"Grid shard {shard_i}/{shard_n}: running "
                    f"{len(indexed_scenarios)} of {len(scenario_params_list)} "
                    "scenarios")

    if shard is not None:
        # a re-run reuses the deterministic sharded folder — a stale done
        # marker from a previous run must not let merge_shards.py merge
        # THIS run's partial csv, and appending to the previous run's csv
        # would silently duplicate its rows
        (experiment_path / f".shard{shard[0]}.done").unlink(missing_ok=True)
        (experiment_path / results_name).unlink(missing_ok=True)

    validate_scenario_list([p for _, p in indexed_scenarios], experiment_path)

    for scenario_id, scenario_params in indexed_scenarios:
        logger.info(f"Scenario {scenario_id + 1}/{len(scenario_params_list)}: "
                    f"{scenario_params}")

    utils.set_log_file(experiment_path)

    for i in range(n_repeats):
        logger.info(f"Repeat {i + 1}/{n_repeats}")
        for scenario_id, scenario_params in indexed_scenarios:
            logger.info(f"Scenario {scenario_id + 1}/{len(scenario_params_list)}")
            current_scenario = Scenario(**scenario_params,
                                        experiment_path=experiment_path,
                                        scenario_id=scenario_id + 1,
                                        repeats_count=i + 1)
            current_scenario.run()

            df_results = current_scenario.to_dataframe()
            df_results["random_state"] = i
            df_results["scenario_id"] = scenario_id

            results_path = experiment_path / results_name
            with open(results_path, "a") as f:
                df_results.to_csv(f, header=f.tell() == 0, index=False)
            logger.info(f"Results saved to {os.path.relpath(results_path)}")
    if shard is not None:
        # completion marker for scripts/merge_shards.py: csv presence can't
        # signal "host finished" (the file appears after the first scenario
        # and a shard whose slice is empty never writes one)
        (experiment_path / f".shard{shard[0]}.done").touch()
    return 0


if __name__ == "__main__":
    sys.exit(main())
